"""Round-3 experiment: dsm kernel op-count levers, measured one at a time.

Baseline (round 2): curve_pallas.verify_tail at blk=256 ~1.4-2.0 us/lane;
full verify 391 K/s.  The VPU-bound analysis (docs/perf_ceiling.md) says
throughput now only moves with per-lane elementwise-op reductions:

  L1 tskip   — dbl-2008-hwcd never READS the input T, so doubles 1..3 of
               each 4-double window run and the affine add that ends a
               window can skip producing T: 192 + 64 of ~2048 + 384 muls.
  L2 signed  — signed 4-bit digits (-8..8): variable table shrinks
               [0..15]A -> [0..8]A (7 fewer _addfull = 63 muls), selects
               go 15-where -> 8-where + cheap conditional negate, and
               VMEM falls ~40% (headroom for larger blk).
  L3 fold    — decomposed 2^264 fold (19c split into 12-bit limb
               contributions) + single carry pass replaces the
               3-pass weak_reduce tail of _reduce44: ~80 el-ops/mul.
  L4 ladder  — accumulate the shifted MAC rows with .at[i:i+22].add
               (masked 3-tile op) instead of concat into 44 rows
               (6-tile op): tests whether Mosaic lowers the slice-add
               cheaply.
  L5 blk     — sweep 128/256/512 with the smaller signed tables.

Methodology per tools/_bench.py: slope over two chain lengths,
np.asarray sync, dispatch amortization.  Correctness: every variant is
checked bit-exact against the XLA double_scalar_mul_base on random
inputs before it is timed.
"""

import functools
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from _bench import slope, timed  # noqa: E402

from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import curve_pallas as cp
from firedancer_tpu.ops import f25519 as fe
from firedancer_tpu.ops import scalar25519 as sc

NL = fe.NLIMB
MASK = fe.MASK
B12 = fe.B
NWIN = 64


# ---------------------------------------------------------------- reductions


def _reduce44_decomp(c):
    """L3: (44, blk) -> NORMAL (22, blk) with a decomposed fold.

    After the two in-space carry passes every column is <= ~4184.  The
    classic fold r = c_lo + c_hi * 9728 then needs weak_reduce(passes=3)
    because limb 21's carry re-enters limb 0 scaled by 9728.  Instead
    split e_i = c_hi_i * 19 (<= 79496) into its 2^9-shifted limb
    contributions  lo_i = (e_i << 9) & MASK  ->  limb i,
                   hi_i = e_i >> 3           ->  limb i+1,
    apply the >=2^255 fold on the top limb FIRST, and finish with ONE
    parallel carry pass.  Bounds: r_i <= 4184 + 4095 + 9937 = 18216;
    after top-fold limb0 <= 43263; one pass leaves every limb <= 4105.
    """
    for _ in range(2):
        lo = c & MASK
        hi = c >> B12
        c = jnp.concatenate([lo[:1], lo[1:] + hi[:-1]], axis=0)
    d, ch = c[:NL], c[NL:]
    e = ch * 19                                     # <= 79496 (17 bits)
    lo = (e << 9) & MASK                            # contribution to limb i
    hi = e >> 3                                     # to limb i+1 (e<<9 >>12)
    r = d + lo + jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    # c[43] is structurally zero so hi[21] (-> limb 22) is zero: nothing lost
    t = r[NL - 1 :] >> 3
    r = jnp.concatenate([r[:1] + t * 19, r[1 : NL - 1], r[NL - 1 :] & 7],
                        axis=0)
    lo = r & MASK
    hi = r >> B12
    return jnp.concatenate(
        [lo[:1] + hi[NL - 1 :] * fe.FOLD264, lo[1:] + hi[: NL - 1]], axis=0)


def _mk_mulw(reduce44, ladder):
    if ladder == "concat":
        def _mulw(a, b):
            z = jnp.zeros_like(a)
            acc = None
            for i in range(NL):
                t = b * a[i : i + 1]
                parts = ([z[:i]] if i else []) + [t, z[: NL - i]]
                row = jnp.concatenate(parts, axis=0)
                acc = row if acc is None else acc + row
            return reduce44(acc)
    elif ladder == "split":
        # two (22, blk) accumulators (columns 0..21 / 22..43): each MAC
        # row lands as two 22-row adds instead of one concat-to-44 add —
        # same el-ops, tests which shape Mosaic schedules better
        def _mulw(a, b):
            z = jnp.zeros_like(a)
            acc_lo = jnp.zeros_like(a)
            acc_hi = jnp.zeros_like(a)
            for i in range(NL):
                t = b * a[i : i + 1]
                if i == 0:
                    acc_lo = acc_lo + t
                else:
                    acc_lo = acc_lo + jnp.concatenate(
                        [z[:i], t[: NL - i]], axis=0)
                    acc_hi = acc_hi + jnp.concatenate(
                        [t[NL - i :], z[: NL - i]], axis=0)
            return reduce44(jnp.concatenate([acc_lo, acc_hi], axis=0))
    else:
        raise ValueError(ladder)
    return _mulw


def _mk_sqrw(reduce44, ladder):
    def _sqrw(a):
        z = jnp.zeros_like(a)
        z44 = jnp.concatenate([z, z], axis=0)
        acc = None
        for i in range(NL - 1):
            t = a[i + 1 :] * a[i : i + 1]
            row = jnp.concatenate([z44[: 2 * i + 1], t, z[: NL - i]], axis=0)
            acc = row if acc is None else acc + row
        acc = acc + acc
        diag = a * a
        de = jnp.stack([diag, jnp.zeros_like(diag)], axis=1).reshape(
            2 * NL, *diag.shape[1:])
        acc = acc + de
        return reduce44(acc)

    def _cat(parts):
        parts = [p for p in parts if p.shape[0]]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                                axis=0)

    def _sqrw_split(a):
        z = jnp.zeros_like(a)
        acc_lo = jnp.zeros_like(a)
        acc_hi = jnp.zeros_like(a)
        for i in range(NL - 1):
            t = a[i + 1 :] * a[i : i + 1]   # rows i+1..21 -> cols 2i+1..i+21
            lo = 2 * i + 1
            ln = NL - 1 - i
            n_lo = max(0, min(ln, NL - lo))
            if n_lo:
                acc_lo = acc_lo + _cat(
                    [z[:lo], t[:n_lo], z[: NL - lo - n_lo]])
            if ln - n_lo:
                start = max(lo, NL) - NL
                acc_hi = acc_hi + _cat(
                    [z[:start], t[n_lo:], z[: NL - start - (ln - n_lo)]])
        acc = jnp.concatenate([acc_lo, acc_hi], axis=0)
        acc = acc + acc
        diag = a * a
        de = jnp.stack([diag, jnp.zeros_like(diag)], axis=1).reshape(
            2 * NL, *diag.shape[1:])
        return reduce44(acc + de)

    return _sqrw_split if ladder == "split" else _sqrw


# ---------------------------------------------------------------- chain body


def _make_chain(tskip, signed, fold, ladder):
    """Returns kernel_body(sw..., a_pt, blk) closing over the lever set.
    Window inputs: unsigned -> (64, blk) u32 digits 0..15;
                   signed   -> mag (64, blk) 0..8 and sgn (64, blk) 0/1."""
    reduce44 = _reduce44_decomp if fold == "decomp" else cp._reduce44
    _mulw = _mk_mulw(reduce44, ladder)
    _sqrw = _mk_sqrw(reduce44, ladder)
    _wr = cp._wr

    def _addw(a, b):
        return _wr(a + b, passes=1)

    def _subw(a, b, bias):
        return _wr(a + bias - b, passes=1)

    def _doublew(p, bias, want_t):
        XX = _sqrw(p.X)
        YY = _sqrw(p.Y)
        ZZ = _sqrw(p.Z)
        ZZ2 = _addw(ZZ, ZZ)
        XpY2 = _sqrw(p.X + p.Y)
        Yp = _addw(YY, XX)
        Ym = _subw(YY, XX, bias)
        Ec = _subw(XpY2, Yp, bias)
        Tc = _subw(ZZ2, Ym, bias)
        return cp._Pt(_mulw(Ec, Tc), _mulw(Yp, Ym), _mulw(Ym, Tc),
                      _mulw(Ec, Yp) if want_t else p.T)

    def _addfull(p, q, bias, d2):
        A = _mulw(_subw(p.Y, p.X, bias), _subw(q.Y, q.X, bias))
        Bv = _mulw(p.Y + p.X, q.Y + q.X)
        C = _mulw(_mulw(p.T, q.T), d2)
        ZZ = _mulw(p.Z, q.Z)
        Dv = _addw(ZZ, ZZ)
        E = _subw(Bv, A, bias)
        F = _subw(Dv, C, bias)
        G = _addw(Dv, C)
        H = _addw(Bv, A)
        return cp._Pt(_mulw(E, F), _mulw(G, H), _mulw(F, G), _mulw(E, H))

    def _to_nielsw(p, bias, d2):
        return cp._Niels(_subw(p.Y, p.X, bias), _addw(p.Y, p.X), p.Z,
                         _mulw(p.T, d2))

    def _add_nielsw(p, q, bias):
        A = _mulw(_subw(p.Y, p.X, bias), q.Ym)
        Bv = _mulw(p.Y + p.X, q.Yp)
        C = _mulw(p.T, q.T2d)
        ZZ = _mulw(p.Z, q.Z)
        Dv = _addw(ZZ, ZZ)
        E = _subw(Bv, A, bias)
        F = _subw(Dv, C, bias)
        G = _addw(Dv, C)
        H = _addw(Bv, A)
        return cp._Pt(_mulw(E, F), _mulw(G, H), _mulw(F, G), _mulw(E, H))

    def _add_affine_nielsw(p, ym, yp, t2d, bias, want_t):
        A = _mulw(_subw(p.Y, p.X, bias), ym)
        Bv = _mulw(p.Y + p.X, yp)
        C = _mulw(p.T, t2d)
        Dv = _addw(p.Z, p.Z)
        E = _subw(Bv, A, bias)
        F = _subw(Dv, C, bias)
        G = _addw(Dv, C)
        H = _addw(Bv, A)
        return cp._Pt(_mulw(E, F), _mulw(G, H), _mulw(F, G),
                      _mulw(E, H) if want_t else p.T)

    def _sel_u(entries, idx, nbits):
        bits = [((idx >> k) & 1).astype(bool) for k in range(nbits)]
        cur = list(entries)
        for k in range(nbits):
            m = bits[k]
            cur = [jax.tree_util.tree_map(
                lambda hi, lo: jnp.where(m, hi, lo),
                cur[2 * i + 1], cur[2 * i]) for i in range(len(cur) // 2)]
        return cur[0]

    def _sel_signed_niels(tab9, mag, sgn, bias):
        """tab9: [0..8] Niels entries; mag (1,blk) 0..8, sgn (1,blk) 0/1."""
        e8 = _sel_u(tab9[:8], mag, 3)
        is8 = mag == 8
        pick = jax.tree_util.tree_map(
            lambda a, b: jnp.where(is8, a, b), tab9[8], e8)
        neg = sgn == 1
        ym = jnp.where(neg, pick.Yp, pick.Ym)
        yp = jnp.where(neg, pick.Ym, pick.Yp)
        t2d = jnp.where(neg, _wr(bias - pick.T2d, passes=1), pick.T2d)
        return cp._Niels(ym, yp, pick.Z, t2d)

    def _base_tab_signed():
        t = cv._BASE_TABS
        out = []
        for i in range(9):
            if i == 0:
                ym = yp = fe._to_limbs_py(1)
                t2 = fe._to_limbs_py(0)
                nt2 = fe._to_limbs_py(0)
            else:
                ym = t["Ym"][0, i]
                yp = t["Yp"][0, i]
                t2 = t["T2d"][0, i]
                nt2 = fe._to_limbs_py(
                    (fe.P - fe._from_limbs_py(t["T2d"][0, i])) % fe.P)
            out.append(tuple(fe._limb_const(v, 2) for v in (ym, yp, t2, nt2)))
        return out

    def _sel_signed_base(tab9, mag, sgn):
        e8 = _sel_u(tab9[:8], mag, 3)
        is8 = mag == 8
        ym, yp, t2, nt2 = (jnp.where(is8, a, b)
                           for a, b in zip(tab9[8], e8))
        neg = sgn == 1
        return (jnp.where(neg, yp, ym), jnp.where(neg, ym, yp),
                jnp.where(neg, nt2, t2))

    def chain(sw_refs, kw_refs, a, blk):
        bias = fe._limb_const(fe._BIAS_PY, 2)
        d2 = cp._constw(cv.D2)
        n_tab = 9 if signed else 16
        pts = [cp._identity_k(blk), a]
        for _ in range(n_tab - 2):
            pts.append(_addfull(pts[-1], a, bias, d2))
        tab_a = [_to_nielsw(p, bias, d2) for p in pts]
        tab_b = _base_tab_signed() if signed else cp._base_digit_table()

        def body(i, acc):
            w = NWIN - 1 - i
            for j in range(4):
                acc = _doublew(acc, bias,
                               want_t=(j == 3) if tskip else True)
            if signed:
                km = kw_refs[0][pl.ds(w, 1), :]
                ks = kw_refs[1][pl.ds(w, 1), :]
                acc = _add_nielsw(
                    acc, _sel_signed_niels(tab_a, km, ks, bias), bias)
                sm = sw_refs[0][pl.ds(w, 1), :]
                ss = sw_refs[1][pl.ds(w, 1), :]
                ym, yp, t2d = _sel_signed_base(tab_b, sm, ss)
            else:
                kw = kw_refs[0][pl.ds(w, 1), :]
                acc = _add_nielsw(acc, _sel_u(tab_a, kw, 4), bias)
                sw = sw_refs[0][pl.ds(w, 1), :]
                ym, yp, t2d = _sel_u(tab_b, sw, 4)
            return _add_affine_nielsw(acc, ym, yp, t2d, bias,
                                      want_t=not tskip)

        return jax.lax.fori_loop(0, NWIN, body, cp._identity_k(blk))

    return chain


def signed_digits(windows):
    """(64, B) u32 digits 0..15 -> (mag 0..8, sgn 0/1), value-preserving:
    sum(d_i 16^i) unchanged with d_i in [-8, 8].  Ripple carry low->high;
    top digit of an L-reduced scalar is <= 7 so no overflow."""
    w = np.asarray(windows, dtype=np.int64)
    mag = np.zeros_like(w)
    sgn = np.zeros_like(w)
    carry = np.zeros_like(w[0])
    for i in range(w.shape[0]):
        d = w[i] + carry
        over = d > 8
        d = np.where(over, d - 16, d)
        carry = over.astype(np.int64)
        sgn[i] = (d < 0).astype(np.int64)
        mag[i] = np.abs(d)
    assert not carry.any(), "top-window overflow"
    return (jnp.asarray(mag.astype(np.uint32)),
            jnp.asarray(sgn.astype(np.uint32)))


def make_dsm(tskip=False, signed=False, fold="wr3", ladder="concat",
             blk=256, steps=1, batch=4096, interpret=False):
    """steps = number of back-to-back dsm chains (slope timing)."""
    chain = _make_chain(tskip, signed, fold, ladder)
    rng = np.random.default_rng(7)
    s_np = rng.integers(0, 16, size=(NWIN, batch), dtype=np.uint32)
    k_np = rng.integers(0, 16, size=(NWIN, batch), dtype=np.uint32)
    # top window <= 7 (every L-reduced scalar satisfies this; keeps the
    # signed recoding carry-free at the top)
    s_np[-1] &= 7
    k_np[-1] &= 7
    a = rand_valid_point(rng, batch)

    win_spec = pl.BlockSpec((NWIN, blk), lambda i: (0, i))
    pt_spec = pl.BlockSpec((NL, blk), lambda i: (0, i))

    if signed:
        sm, ss = signed_digits(s_np)
        km, ks = signed_digits(k_np)
        win_args = (sm, ss, km, ks)
        n_win_in = 4
    else:
        win_args = (jnp.asarray(s_np), jnp.asarray(k_np))
        n_win_in = 2

    def kernel(*refs):
        win_refs = refs[:n_win_in]
        ax, ay, az, at = refs[n_win_in : n_win_in + 4]
        xo, yo, zo, to = refs[n_win_in + 4 :]
        a_pt = cp._Pt(ax[...], ay[...], az[...], at[...])
        if signed:
            sw_refs = win_refs[0:2]
            kw_refs = win_refs[2:4]
        else:
            sw_refs = (win_refs[0],)
            kw_refs = (win_refs[1],)

        def body(i, pt):
            # chain the output back in as the next A (data dependence
            # for slope timing; windows stay fixed)
            return chain(sw_refs, kw_refs, pt, blk)

        out = jax.lax.fori_loop(0, steps, body, a_pt)
        xo[...] = out.X
        yo[...] = out.Y
        zo[...] = out.Z
        to[...] = out.T

    @jax.jit
    def f(*args):
        outs = pl.pallas_call(
            kernel,
            out_shape=[jax.ShapeDtypeStruct((NL, batch), jnp.uint32)] * 4,
            grid=(batch // blk,),
            in_specs=[win_spec] * n_win_in + [pt_spec] * 4,
            out_specs=[pt_spec] * 4,
            interpret=interpret,
        )(*args)
        return outs

    return f, (*win_args, a.X, a.Y, a.Z, a.T), (s_np, k_np, a)


def rand_valid_point(rng, batch):
    """Random curve points: [r]B for random r (host), as (22, batch) planes."""
    from firedancer_tpu.ops import ed25519 as ed
    pts = []
    for _ in range(min(batch, 8)):
        r = int.from_bytes(rng.bytes(32), "little") % (2**252)
        pts.append(ed._scalar_mul_base_host(r))
    xs = np.zeros((NL, batch), np.uint32)
    ys = np.zeros((NL, batch), np.uint32)
    zs = np.zeros((NL, batch), np.uint32)
    ts = np.zeros((NL, batch), np.uint32)
    for i in range(batch):
        X, Y, Z, T = pts[i % len(pts)]
        zi = pow(Z, fe.P - 2, fe.P)
        x, y = X * zi % fe.P, Y * zi % fe.P
        xs[:, i] = fe._to_limbs_py(x)
        ys[:, i] = fe._to_limbs_py(y)
        zs[:, i] = fe._to_limbs_py(1)
        ts[:, i] = fe._to_limbs_py(x * y % fe.P)
    return cv.Point(jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs),
                    jnp.asarray(ts))


def check_variant(batch=256, blk=128, n_check=24, **levers):
    """One dsm chain, steps=1, vs the host (python-int) reference — the
    XLA dsm graph takes minutes to compile on this box, host ints don't."""
    from firedancer_tpu.ops import ed25519 as ed
    f, args, (s_np, k_np, a) = make_dsm(blk=blk, steps=1, batch=batch,
                                        **levers)
    outs = [np.asarray(o) for o in f(*args)]
    P = fe.P
    for lane in range(0, batch, max(1, batch // n_check)):
        s = sum(int(s_np[i, lane]) << (4 * i) for i in range(NWIN))
        k = sum(int(k_np[i, lane]) << (4 * i) for i in range(NWIN))
        A = tuple(fe.to_int(np.asarray(t)[:, lane]) for t in a)
        want = ed._pt_add_host(
            ed._scalar_mul_base_host(s),
            ed._scalar_mul_host(k, (A[0], A[1], A[2], A[3])))
        zi = pow(fe.to_int(outs[2][:, lane]), P - 2, P)
        gx = fe.to_int(outs[0][:, lane]) * zi % P
        gy = fe.to_int(outs[1][:, lane]) * zi % P
        wzi = pow(want[2], P - 2, P)
        assert gx == want[0] * wzi % P and gy == want[1] * wzi % P, \
            f"{levers}: lane {lane} mismatch"
    print(f"correct: {levers}", flush=True)


def main():
    base = dict(tskip=False, signed=False, fold="wr3", ladder="concat")
    variants = [
        ("baseline", {}),
        ("tskip", dict(tskip=True)),
        ("fold=decomp", dict(fold="decomp")),
        ("ladder=split", dict(ladder="split")),
        ("signed", dict(signed=True)),
        ("tskip+fold", dict(tskip=True, fold="decomp")),
        ("tskip+fold+signed", dict(tskip=True, fold="decomp", signed=True)),
        ("all", dict(tskip=True, fold="decomp", signed=True,
                     ladder="split")),
    ]
    results = {}
    for name, kw in variants:
        levers = {**base, **kw}
        try:
            check_variant(**levers)
        except Exception as e:
            print(f"{name} FAILED check: {type(e).__name__}: {e}",
                  flush=True)
            continue
        try:
            r = slope(
                f"dsm[{name}] blk=256",
                lambda s, lv=levers: make_dsm(
                    blk=256, steps=s, batch=4096, **lv)[:2],
                2, 6, 4096, "dsm/lane")
            results[(name, 256)] = r
        except Exception as e:
            print(f"dsm[{name}] blk=256 FAILED: "
                  f"{type(e).__name__}: {str(e)[:200]}", flush=True)
    if results:
        best = min(results, key=results.get)[0]
        levers = {**base, **dict(variants)[best]}
        for blk in (128, 512):
            try:
                r = slope(
                    f"dsm[{best}] blk={blk}",
                    lambda s, lv=levers, b=blk: make_dsm(
                        blk=b, steps=s, batch=4096, **lv)[:2],
                    2, 6, 4096, "dsm/lane")
                results[(best, blk)] = r
            except Exception as e:
                print(f"dsm[{best}] blk={blk} FAILED: "
                      f"{type(e).__name__}: {str(e)[:200]}", flush=True)
    print("\n=== summary (ns/dsm/lane) ===", flush=True)
    for (name, blk), r in sorted(results.items(), key=lambda kv: kv[1]):
        print(f"{name:24s} blk={blk:4d}  {r*1e9:9.1f}", flush=True)


if __name__ == "__main__":
    main()
