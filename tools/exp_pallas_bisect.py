"""Bisect the dsm-kernel slowdown: start from the fast double-chain kernel
((22,1,blk) fe geometry) and add dsm features one at a time."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from _bench import timed

from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import f25519 as fe

# fe constants are array constants in the jit path (fast XLA compiles) but
# Mosaic rejects captured arrays inside kernels — swap in the scalar-literal
# constructors for this experiment's fe-code-inside-pallas usage.
fe.const = lambda v, ndim=1: fe._limb_const(fe._to_limbs_py(v % fe.P), ndim)
fe._bias = lambda ndim: fe._limb_const(fe._BIAS_PY, ndim)

BATCH = 4096
BLK = 128
STEPS = 256  # doublings total, to mirror the dsm chain


def _ones_k(blk):
    return jnp.concatenate(
        [jnp.full((1, 1, blk), 1, jnp.uint32),
         jnp.zeros((fe.NLIMB - 1, 1, blk), jnp.uint32)], axis=0)


def _identity_k(blk):
    z = jnp.zeros((fe.NLIMB, 1, blk), jnp.uint32)
    one = _ones_k(blk)
    return cv.Point(z, one, one, z)


def _select_list(entries, idx, nbits=4):
    bits = [((idx >> k) & 1).astype(bool) for k in range(nbits)]
    cur = list(entries)
    for k in range(nbits):
        m = bits[k]
        cur = [
            jax.tree_util.tree_map(
                lambda hi, lo: jnp.where(m, hi, lo), cur[2 * i + 1], cur[2 * i]
            )
            for i in range(len(cur) // 2)
        ]
    return cur[0]


def make(variant):
    rng = np.random.default_rng(0)
    kw = jnp.asarray(rng.integers(0, 16, size=(64, BATCH), dtype=np.uint32))
    a4 = [jnp.asarray(rng.integers(0, 4096, size=(22, BATCH),
                                   dtype=np.uint32)) for _ in range(2)]
    p = cv.Point(a4[0], a4[1], fe.ones((BATCH,)), fe.zeros((BATCH,)))

    def kernel(kw_ref, ax, ay, az, at, xo, yo, zo, to):
        pt = cv.Point(ax[...][:, None, :], ay[...][:, None, :],
                      az[...][:, None, :], at[...][:, None, :])

        if variant == "chain":
            # flat fori over 256 doubles (known-fast shape)
            pt = jax.lax.fori_loop(
                0, STEPS, lambda i, q: cv.double(q), pt)
        elif variant == "nested":
            # 64 x fori(4) nesting like dsm
            def body(i, q):
                return jax.lax.fori_loop(
                    0, 4, lambda _, r: cv.double(r), q)
            pt = jax.lax.fori_loop(0, 64, body, pt)
        elif variant == "unroll4":
            def body(i, q):
                for _ in range(4):
                    q = cv.double(q)
                return q
            pt = jax.lax.fori_loop(0, 64, body, pt)
        elif variant == "dynread":
            def body(i, q):
                for _ in range(4):
                    q = cv.double(q)
                w = 63 - i
                kwv = kw_ref[pl.ds(w, 1), :]
                # consume kwv cheaply: add it into X's low limb
                return cv.Point(q.X + (kwv * 0)[None], q.Y, q.Z, q.T)
            pt = jax.lax.fori_loop(0, 64, body, pt)
        elif variant in ("table", "tableadd"):
            base = pt
            pts = [_identity_k(BLK), base]
            for _ in range(14):
                pts.append(cv.add(pts[-1], base))
            tab = [cv.to_niels(q) for q in pts]

            def body(i, q):
                for _ in range(4):
                    q = cv.double(q)
                w = 63 - i
                kwv = kw_ref[pl.ds(w, 1), :]
                sel = _select_list(tab, kwv)
                if variant == "tableadd":
                    return cv.add_niels(q, sel)
                return cv.Point(q.X + (sel.Ym * 0), q.Y, q.Z, q.T)
            pt = jax.lax.fori_loop(0, 64, body, pt)

        xo[...] = pt.X[:, 0, :]
        yo[...] = pt.Y[:, 0, :]
        zo[...] = pt.Z[:, 0, :]
        to[...] = pt.T[:, 0, :]

    win_spec = pl.BlockSpec((64, BLK), lambda i: (0, i))
    pt_spec = pl.BlockSpec((fe.NLIMB, BLK), lambda i: (0, i))

    @jax.jit
    def f(kw, pt):
        outs = pl.pallas_call(
            kernel,
            out_shape=[jax.ShapeDtypeStruct((fe.NLIMB, BATCH), jnp.uint32)]
            * 4,
            grid=(BATCH // BLK,),
            in_specs=[win_spec] + [pt_spec] * 4,
            out_specs=[pt_spec] * 4,
        )(kw, pt.X, pt.Y, pt.Z, pt.T)
        return cv.Point(*outs)

    return f, (kw, p)


def main():
    for variant in ("chain", "nested", "unroll4", "dynread", "table",
                    "tableadd"):
        try:
            f, args = make(variant)
            t = timed(f, *args)
            print(f"{variant:10s}: {t*1e3:7.1f} ms "
                  f"({t/BATCH/STEPS*1e9:6.2f} ns/dbl/lane-equiv)", flush=True)
        except Exception as e:
            print(f"{variant:10s} FAILED: {str(e)[-120:]}", flush=True)


if __name__ == "__main__":
    main()
